"""Scale-out serving tests (ISSUE 13): the fleet router's contracts.

Four surfaces under test:

* **hardened ingress** — body-size 413, per-connection read timeout,
  bounded concurrent connections, each counted by reason in
  ``tftpu_serving_rejections_total``;
* **lifecycle** — ``Server.state`` (``starting|running|draining|
  stopped``) as the one routing source of truth, ``drain()`` triggered
  over HTTP for rolling restarts;
* **redrive exactly-once** — a replica failing mid-request produces
  exactly one response per affected request, pinned against BOTH crash
  windows (crash-before-dispatch and crash-after-dispatch-before-reply,
  the latter deduplicated by idempotency key, not double-executed);
* **the fleet acceptance** — kill -9 of a replica under open-loop load:
  every admitted request gets exactly one response, the router never
  routes to the dead replica, and the restarted replica warms from the
  shared store with ZERO XLA compiles.
"""

import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.serving import (
    Router,
    RouterConfig,
    Server,
    ServingConfig,
    ServingFleet,
    serve_http,
)
from tensorframes_tpu.serving import metrics as sm
from tensorframes_tpu.serving.replica import publish_card, read_cards
from tensorframes_tpu.serving.router import ReplicaHandle

WIDTH = 4


def _schema(width=WIDTH):
    return tfs.Schema([
        tfs.ColumnInfo(
            "x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, width))
        )
    ])


def _program(width=WIDTH):
    holder = type("F", (), {"schema": _schema(width)})()
    return tfs.compile_program(
        lambda x: {"y": x * 2.0 + 1.0}, holder, block=False
    )


def _server(**cfg_kwargs) -> Server:
    cfg = dict(max_batch_rows=8, max_latency_s=0.002, max_queue_rows=128)
    cfg.update(cfg_kwargs)
    srv = Server(ServingConfig(**cfg))
    srv.register("score", _program())
    return srv


def _post(url, body=None, raw=None, timeout=20):
    data = raw if raw is not None else json.dumps(body or {}).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# hardened ingress (ISSUE 13 satellite 1)
# ---------------------------------------------------------------------------

def test_http_body_too_large_413():
    srv = _server()
    srv.start()
    httpd = serve_http(srv, max_body_bytes=512)
    port = httpd.server_address[1]
    before = sm.http_rejected("body_too_large").value
    try:
        st, body = _post(
            f"http://127.0.0.1:{port}/v1/score", raw=b"x" * 2048
        )
        assert st == 413
        assert body["reason"] == "body_too_large"
        assert sm.http_rejected("body_too_large").value == before + 1
        # a compliant request still lands afterwards
        st, body = _post(
            f"http://127.0.0.1:{port}/v1/score",
            {"inputs": {"x": [[1.0] * WIDTH]}},
        )
        assert st == 200
    finally:
        httpd.shutdown()
        srv.stop(drain=True)


def test_http_read_timeout_counted_and_bounded():
    srv = _server()
    srv.start()
    httpd = serve_http(srv, read_timeout_s=0.3)
    port = httpd.server_address[1]
    before = sm.http_rejected("read_timeout").value
    try:
        # slowloris body: declare 100 bytes, send 10, stall
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.sendall(
            b"POST /v1/score HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 100\r\n\r\n" + b"0123456789"
        )
        t0 = time.monotonic()
        s.settimeout(10)
        data = b""
        try:
            while b"\r\n\r\n" not in data:
                chunk = s.recv(4096)
                if not chunk:
                    break
                data += chunk
        except OSError:
            data = data or b""
        elapsed = time.monotonic() - t0
        s.close()
        # bounded: the handler thread gave up on the clock, not on the
        # attacker's schedule, and replied 408 while the socket allowed
        assert elapsed < 5.0
        assert b"408" in data.split(b"\r\n", 1)[0]
        assert sm.http_rejected("read_timeout").value == before + 1
    finally:
        httpd.shutdown()
        srv.stop(drain=True)


def test_http_connection_limit_503():
    srv = _server()
    srv.start()
    httpd = serve_http(srv, max_connections=1, read_timeout_s=5.0)
    port = httpd.server_address[1]
    before = sm.http_rejected("conn_limit").value
    holder = None
    try:
        # occupy the single slot with a half-sent request
        holder = socket.create_connection(("127.0.0.1", port), timeout=10)
        holder.sendall(b"POST /v1/score HTTP/1.1\r\nHost: x\r\n")
        deadline = time.monotonic() + 5.0
        st = None
        while time.monotonic() < deadline:
            st, body = _post(
                f"http://127.0.0.1:{port}/v1/score",
                {"inputs": {"x": [[1.0] * WIDTH]}}, timeout=5,
            )
            if st == 503:
                break
            time.sleep(0.05)
        assert st == 503
        assert body["reason"] == "conn_limit"
        assert sm.http_rejected("conn_limit").value > before
    finally:
        if holder is not None:
            holder.close()
        httpd.shutdown()
        srv.stop(drain=True)


# ---------------------------------------------------------------------------
# lifecycle state + HTTP drain (satellite 2)
# ---------------------------------------------------------------------------

def test_server_lifecycle_state_walk():
    srv = _server()
    assert srv.state == "stopped"
    assert srv.stats()["state"] == "stopped"
    srv.start()
    assert srv.state == "running"
    assert srv.stats()["state"] == "running"
    assert srv.stats()["running"] is True
    srv.drain(wait=True)
    assert srv.state == "stopped"
    assert srv.stats()["running"] is False


def test_drain_triggered_over_http_closes_admission():
    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    port = httpd.server_address[1]
    try:
        st, body = _post(f"http://127.0.0.1:{port}/admin/drain")
        assert st == 202
        assert body["state"] in ("draining", "stopped")
        deadline = time.monotonic() + 10.0
        while srv.state != "stopped" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert srv.state == "stopped"
        st, h = _get(f"http://127.0.0.1:{port}/healthz")
        assert h["state"] == "stopped"
        # admission is closed: submits shed with the 503 taxonomy
        st, body = _post(
            f"http://127.0.0.1:{port}/v1/score",
            {"inputs": {"x": [[1.0] * WIDTH]}},
        )
        assert st == 503
        assert body["reason"] == "closed"
    finally:
        httpd.shutdown()
        srv.stop(drain=True)


def test_stats_carries_process_compile_counters():
    srv = _server()
    proc = srv.stats()["process"]
    assert set(proc) == {
        "xla_compiles", "compile_cache_hits", "compile_cache_misses",
    }
    assert all(isinstance(v, int) for v in proc.values())


# ---------------------------------------------------------------------------
# idempotency dedup (the redrive building block)
# ---------------------------------------------------------------------------

def test_idempotent_submit_joins_original_future(monkeypatch):
    from tensorframes_tpu.serving.server import Endpoint

    calls = []
    orig = Endpoint.dispatch

    def counting(self, feeds, rows):
        calls.append(rows)
        return orig(self, feeds, rows)

    monkeypatch.setattr(Endpoint, "dispatch", counting)
    srv = _server()
    srv.start()
    try:
        before = sm.IDEMPOTENT_DEDUP.value
        f1 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="k-1")
        f2 = srv.submit("score", {"x": np.zeros((1, WIDTH), np.float32)},
                        idempotency_key="k-1")
        assert f2 is f1  # joined, not re-executed
        r1 = f1.result(20)
        assert sm.IDEMPOTENT_DEDUP.value == before + 1
        # different key executes independently
        f3 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="k-2")
        r3 = f3.result(20)
        np.testing.assert_array_equal(r1["y"], r3["y"])
        assert len(calls) == 2
    finally:
        srv.stop(drain=True)


def test_idempotency_cache_bound_evicts_fifo():
    srv = Server(ServingConfig(
        max_batch_rows=8, max_latency_s=0.001, idempotency_cache=2,
    ))
    srv.register("score", _program())
    srv.start()
    try:
        f1 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="a")
        srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                   idempotency_key="b")
        srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                   idempotency_key="c")  # evicts "a"
        f4 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="a")
        assert f4 is not f1  # past the bound: a fresh execution
    finally:
        srv.stop(drain=True)


def test_idempotency_scoped_per_endpoint():
    srv = _server()
    srv.register("score2", _program())
    srv.start()
    try:
        f1 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="shared")
        # the SAME client key against a DIFFERENT endpoint is a
        # different operation — never a cache hit
        f2 = srv.submit("score2", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="shared")
        assert f2 is not f1
    finally:
        srv.stop(drain=True)


def test_idempotency_ttl_expires_entries():
    srv = Server(ServingConfig(
        max_batch_rows=8, max_latency_s=0.001, idempotency_ttl_s=0.2,
    ))
    srv.register("score", _program())
    srv.start()
    try:
        f1 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="t")
        f1.result(20)
        time.sleep(0.35)  # past the TTL: the entry (and its pinned
        # result arrays) must be gone — dedup covers the redrive
        # window, not steady-state history
        f2 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="t")
        assert f2 is not f1
    finally:
        srv.stop(drain=True)


def test_router_ingress_malformed_deadline_is_400():
    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    router = Router(
        replicas={0: f"127.0.0.1:{httpd.server_address[1]}"},
        config=RouterConfig(poll_s=0.05),
    )
    router.start()
    ingress = router.serve()
    port = ingress.server_address[1]
    try:
        deadline = time.monotonic() + 10.0
        while router.live_count() < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        # a non-numeric deadline must be a clean 400 through the
        # ingress — never a dropped connection from a dead handler
        for bad in ("soon", [1], {"s": 1}):
            st, body = _post(
                f"http://127.0.0.1:{port}/v1/score",
                {"inputs": {"x": [[1.0] * WIDTH]}, "deadline_s": bad},
            )
            assert st == 400, (bad, st, body)
        st, body = _post(
            f"http://127.0.0.1:{port}/v1/score",
            {"inputs": {"x": [[1.0] * WIDTH]}, "deadline_s": -1},
        )
        assert st == 400
    finally:
        router.stop()
        httpd.shutdown()
        srv.stop(drain=True)


def test_idempotency_disabled_by_zero_cache():
    srv = Server(ServingConfig(
        max_batch_rows=8, max_latency_s=0.001, idempotency_cache=0,
    ))
    srv.register("score", _program())
    srv.start()
    try:
        f1 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="k")
        f2 = srv.submit("score", {"x": np.ones((1, WIDTH), np.float32)},
                        idempotency_key="k")
        assert f2 is not f1
    finally:
        srv.stop(drain=True)


# ---------------------------------------------------------------------------
# router placement (unit: no HTTP)
# ---------------------------------------------------------------------------

def _handle(rank, state, queued=0, inflight=0):
    h = ReplicaHandle(rank, f"127.0.0.1:{9000 + rank}")
    h.state = state
    h.queued_rows = queued
    h.inflight = inflight
    return h


def test_pick_prefers_lowest_load_and_skips_non_running():
    r = Router()
    r._replicas = {
        0: _handle(0, "running", queued=10),
        1: _handle(1, "running", queued=2, inflight=1),
        2: _handle(2, "draining", queued=0),   # never picked
        3: _handle(3, "dead", queued=0),       # never picked
        4: _handle(4, "starting", queued=0),   # never picked
        5: _handle(5, "stopped", queued=0),    # never picked
    }
    h = r._pick(set())
    assert h.rank == 1  # lowest (queued + inflight) among running
    # _pick charged one in-flight unit; the same replica still wins
    # until its load passes rank 0's
    assert h.inflight == 2
    r._release(h)
    assert h.inflight == 1
    assert r._pick({0, 1}) is None  # every running replica excluded


def test_pick_none_when_no_replicas():
    r = Router(config=RouterConfig(no_replica_wait_s=0.1))
    status, body = r.dispatch("score", {"inputs": {}})
    assert status == 503
    assert body["reason"] == "no_replica"


# ---------------------------------------------------------------------------
# redrive exactly-once: the two crash windows (satellite 4)
# ---------------------------------------------------------------------------

class _CrashingReplica:
    """A fake replica HTTP server pinning the crash windows: answers
    healthz as a running replica (so the router routes to it), and for
    POST /v1/* either drops the connection immediately
    (``crash-before-dispatch`` — the request never executed) or
    forwards to a REAL replica first and drops the reply
    (``crash-after-dispatch-before-reply`` — executed, answer lost)."""

    def __init__(self, mode, forward_port=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self
        self.mode = mode
        self.forward_port = forward_port
        self.posts = 0

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._reply(200, {
                    "state": "running", "running": True,
                    "queued_rows": {}, "endpoints": ["score"],
                })

            def do_POST(self):
                outer.posts += 1
                raw = self.rfile.read(
                    int(self.headers.get("Content-Length", 0))
                )
                if outer.mode == "after_dispatch" and outer.forward_port:
                    # execute on the real replica (same idempotency
                    # key), then die before relaying the answer
                    _post(
                        f"http://127.0.0.1:{outer.forward_port}"
                        f"{self.path}", raw=raw,
                    )
                # both windows end the same way: the socket dies with
                # no reply — exactly what a SIGKILLed replica leaves.
                # shutdown() (not close()) actually sends the FIN: the
                # rfile/wfile dups keep the fd open past close()
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    @property
    def port(self):
        return self.httpd.server_address[1]

    def stop(self):
        self.httpd.shutdown()


@pytest.mark.parametrize("window", ["before_dispatch", "after_dispatch"])
def test_redrive_exactly_once_through_crash_windows(window, monkeypatch):
    from tensorframes_tpu.serving.server import Endpoint

    executions = []
    orig = Endpoint.dispatch

    def counting(self, feeds, rows):
        executions.append(rows)
        return orig(self, feeds, rows)

    monkeypatch.setattr(Endpoint, "dispatch", counting)

    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    real_port = httpd.server_address[1]
    crasher = _CrashingReplica(window, forward_port=real_port)
    router = Router(
        replicas={0: f"127.0.0.1:{crasher.port}",
                  1: f"127.0.0.1:{real_port}"},
        config=RouterConfig(poll_s=0.05),
    )
    router.start()
    try:
        deadline = time.monotonic() + 10.0
        while router.live_count() < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.live_count() == 2
        before_redrives = sm.ROUTER_REDRIVES.value
        # rank 0 (the crasher, load 0) wins the tie-break → the first
        # attempt lands on the crash window, the redrive on the survivor
        status, body = router.dispatch(
            "score", {"inputs": {"x": [[1.0] * WIDTH]}},
            deadline_s=20.0,
        )
        assert status == 200, body
        np.testing.assert_allclose(
            np.asarray(body["outputs"]["y"]), [[3.0] * WIDTH]
        )
        assert body["replica"] == 1
        assert crasher.posts == 1
        assert sm.ROUTER_REDRIVES.value == before_redrives + 1
        # EXACTLY ONE response (the return above) and — the dedup
        # pin — exactly one execution even in the window where the
        # dying replica already dispatched it
        assert len(executions) == 1
        assert router.counters()["redrives"] == 1
    finally:
        router.stop()
        crasher.stop()
        httpd.shutdown()
        srv.stop(drain=True)


def test_router_delay_chaos_expires_deadline():
    from tensorframes_tpu.resilience import faults

    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    port = httpd.server_address[1]
    router = Router(
        replicas={0: f"127.0.0.1:{port}"},
        config=RouterConfig(poll_s=0.05),
    )
    router.start()
    try:
        deadline = time.monotonic() + 10.0
        while router.live_count() < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        with faults.inject("router.dispatch", faults.Delay(0.5)):
            status, body = router.dispatch(
                "score", {"inputs": {"x": [[1.0] * WIDTH]}},
                deadline_s=0.25,
            )
        # the stalled dispatch consumed the budget: the replica (or the
        # router's own check) expires it — a counted 504, never a hang
        assert status == 504
    finally:
        router.stop()
        httpd.shutdown()
        srv.stop(drain=True)


def test_router_never_routes_to_draining_replica():
    srv_a, srv_b = _server(), _server()
    srv_a.start()
    srv_b.start()
    httpd_a = serve_http(srv_a)
    httpd_b = serve_http(srv_b)
    router = Router(
        replicas={0: f"127.0.0.1:{httpd_a.server_address[1]}",
                  1: f"127.0.0.1:{httpd_b.server_address[1]}"},
        config=RouterConfig(poll_s=0.05),
    )
    router.start()
    try:
        deadline = time.monotonic() + 10.0
        while router.live_count() < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        srv_a.drain(wait=True)  # rank 0 retires
        deadline = time.monotonic() + 10.0
        while router.live_count() > 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.live_count() == 1
        for i in range(6):
            status, body = router.dispatch(
                "score", {"inputs": {"x": [[float(i)] * WIDTH]}},
                deadline_s=20.0,
            )
            assert status == 200
            assert body["replica"] == 1  # never the drained replica
    finally:
        router.stop()
        httpd_a.shutdown()
        httpd_b.shutdown()
        srv_a.stop(drain=True)
        srv_b.stop(drain=True)


# ---------------------------------------------------------------------------
# replica cards
# ---------------------------------------------------------------------------

def test_scrape_failures_mark_once_running_replica_dead():
    """A static replica (no fleet heartbeats) that WAS serving and
    stops answering healthz must be marked dead after
    ``scrape_fails_dead`` consecutive failures — while a never-ready
    address (still warming) only stays un-routable, never dead."""
    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    port = httpd.server_address[1]
    router = Router(
        replicas={0: f"127.0.0.1:{port}", 1: "127.0.0.1:1"},
        config=RouterConfig(poll_s=0.05, scrape_timeout_s=0.5,
                            scrape_fails_dead=2),
    )
    router.start()
    try:
        deadline = time.monotonic() + 10.0
        while router.live_count() < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.live_count() == 1
        # the never-reachable rank 1 accumulates failures but was never
        # running: un-routable, NOT dead
        time.sleep(0.3)
        assert router.replicas()[1]["state"] != "dead"
        # now the serving replica goes away entirely (server_close
        # releases the listening socket so the port can be re-bound)
        httpd.shutdown()
        httpd.server_close()
        srv.stop(drain=False)
        deadline = time.monotonic() + 10.0
        while router.replicas()[0]["state"] != "dead" \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        snap = router.replicas()[0]
        assert snap["state"] == "dead", snap
        assert "unreachable" in snap["dead_reason"]
        # dead is a routing verdict, not a tombstone: the replica's
        # healthz coming BACK (same address — transient stall over)
        # must resurrect it into the routable set
        srv2 = _server()
        srv2.start()
        httpd2 = serve_http(srv2, port=port)
        try:
            deadline = time.monotonic() + 10.0
            while router.replicas()[0]["state"] != "running" \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            snap = router.replicas()[0]
            assert snap["state"] == "running", snap
            assert snap["dead_reason"] is None
            assert router.live_count() == 1
        finally:
            httpd2.shutdown()
            srv2.stop(drain=True)
    finally:
        router.stop()


def test_replica_cards_roundtrip(tmp_path):
    d = str(tmp_path)
    publish_card(d, rank=0, addr="127.0.0.1", port=1234, run_id="r1")
    publish_card(d, rank=1, addr="127.0.0.1", port=1235, run_id="r1")
    # a restart republishes rank 1 with a fresh port: newest wins
    time.sleep(0.01)
    publish_card(d, rank=1, addr="127.0.0.1", port=1299, run_id="r1",
                 attempt=1)
    cards = read_cards(d, "r1")
    assert sorted(cards) == [0, 1]
    assert cards[1]["port"] == 1299
    assert cards[1]["attempt"] == 1
    assert read_cards(d, "other-run") == {}


# ---------------------------------------------------------------------------
# the fleet acceptance: kill -9 under load (the ISSUE 13 gate)
# ---------------------------------------------------------------------------

def _fleet_cmd():
    return [
        sys.executable, "-m", "tensorframes_tpu.serving.replica_main",
        "--demo", "--max-batch-rows", "8",
    ]


def test_fleet_kill9_under_load_exactly_once_and_zero_compile_restart():
    fleet = ServingFleet(
        _fleet_cmd(), 2, heartbeat_timeout_s=3.0,
        env={"JAX_PLATFORMS": "cpu", "TFTPU_HEARTBEAT_INTERVAL_S": "0.1"},
    )
    fleet.start()
    results = []
    lock = threading.Lock()

    def load(n, tid):
        for i in range(n):
            st, body = _post(
                fleet.url + "/v1/score",
                {"inputs": {"x": [[float((tid * n + i) % 5)] * 8]},
                 "deadline_s": 30.0},
                timeout=60,
            )
            with lock:
                results.append((st, body))
            time.sleep(0.01)

    try:
        threads = [
            threading.Thread(target=load, args=(20, t)) for t in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)
        pid = fleet.kill_replica(1, signal.SIGKILL)
        assert pid is not None
        for t in threads:
            t.join(timeout=120)
        # EXACTLY ONE response per admitted request — success or a
        # counted error, never silence (here the deadline is generous
        # and a survivor exists, so everything must succeed)
        assert len(results) == 60
        assert all(st == 200 for st, _ in results), [
            (st, b) for st, b in results if st != 200
        ][:3]
        # the dead replica was detected and cut from routing
        status = fleet.status()
        assert status["restarts"] == 1
        # ... and its replacement warmed from the shared store with
        # ZERO XLA compiles (the PR 10 property, asserted for serving)
        deadline = time.monotonic() + 60.0
        while 1 not in fleet.restart_reports \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        report = fleet.restart_reports.get(1)
        assert report is not None, fleet.status()
        assert report["xla_compiles"] == 0, report
        assert report["compile_cache_hits"] > 0, report
        # the rejoined fleet serves at full strength again
        fleet.wait_ready(timeout=30.0)
        st, body = _post(
            fleet.url + "/v1/score",
            {"inputs": {"x": [[1.0] * 8]}, "deadline_s": 30.0},
            timeout=60,
        )
        assert st == 200
    finally:
        fleet.stop()


def test_fleet_rolling_restart_via_http_drain():
    """Draining one replica over HTTP (the rolling-restart flow) makes
    it exit CLEAN; the fleet respawns it without consuming the crash
    budget and the router never routed to it while draining."""
    fleet = ServingFleet(
        _fleet_cmd(), 2, heartbeat_timeout_s=3.0,
        env={"JAX_PLATFORMS": "cpu", "TFTPU_HEARTBEAT_INTERVAL_S": "0.1"},
    )
    fleet.start()
    try:
        cards = read_cards(fleet.rendezvous_dir, fleet.run_id)
        addr = f"127.0.0.1:{cards[0]['port']}"
        st, body = _post(f"http://{addr}/admin/drain", {})
        assert st == 202
        # the drained rank exits 0 and respawns with attempt+1
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            snap = fleet.router.replicas().get(0)
            if snap and snap["attempt"] == 1 \
                    and snap["state"] == "running":
                break
            time.sleep(0.1)
        snap = fleet.router.replicas().get(0)
        assert snap and snap["attempt"] == 1, snap
        assert fleet.restarts == 0  # clean exits are budget-free
        assert not fleet.degraded
        st, body = _post(
            fleet.url + "/v1/score",
            {"inputs": {"x": [[2.0] * 8]}, "deadline_s": 30.0},
            timeout=60,
        )
        assert st == 200
    finally:
        fleet.stop()


def test_fleet_wedged_replica_detected_by_heartbeat_and_restarted():
    """A replica that is alive-but-silent (SIGSTOP — the wedged-process
    shape) must be detected by heartbeat staleness, killed, and
    restarted; and the RESTARTED replica must not be re-killed by the
    previous incarnation's stale beat still on disk (the beats are
    judged per-pid) — the restart count stays at exactly 1."""
    fleet = ServingFleet(
        _fleet_cmd(), 2, heartbeat_timeout_s=1.5,
        env={"JAX_PLATFORMS": "cpu", "TFTPU_HEARTBEAT_INTERVAL_S": "0.1"},
    )
    fleet.start()
    try:
        pid = fleet.pid(1)
        os.kill(pid, signal.SIGSTOP)  # wedged: process alive, beats stop
        deadline = time.monotonic() + 90.0
        while 1 not in fleet.restart_reports \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        assert fleet.restart_reports.get(1), fleet.status()
        assert fleet.restarts == 1
        fleet.wait_ready(timeout=30.0)
        # soak past the heartbeat timeout: a stale-beat misjudgment
        # against the fresh incarnation would restart it again here
        time.sleep(2.5)
        assert fleet.restarts == 1, fleet.status()
        assert fleet.router.live_count() == 2
        st, body = _post(
            fleet.url + "/v1/score",
            {"inputs": {"x": [[1.0] * 8]}, "deadline_s": 30.0},
            timeout=60,
        )
        assert st == 200
    finally:
        fleet.stop()


def test_router_metrics_preregistered():
    from tensorframes_tpu.observability.metrics import REGISTRY

    names = {m.name for m in REGISTRY.collect()}
    for want in (
        "tftpu_router_requests_total",
        "tftpu_router_redrives_total",
        "tftpu_router_rejected_total",
        "tftpu_router_replicas_live",
        "tftpu_router_replica_dead_total",
        "tftpu_router_replica_restarts_total",
        "tftpu_router_dispatch_seconds",
        "tftpu_router_request_latency_seconds",
        "tftpu_serving_rejections_total",
        "tftpu_serving_idempotent_dedup_total",
    ):
        assert want in names, want
